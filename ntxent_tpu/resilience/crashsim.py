"""Crash-replay audit: SIGKILL a real training run, prove lossless resume.

PR 1 proved "restart works" (SIGTERM → checkpoint → resume, loss curve
intact). This harness upgrades the claim to "restart is provably
lossless" against the *hard* death — SIGKILL, the no-cleanup signal the
OOM-killer and node loss actually deliver — now that the checkpoint path
writes atomically (training/checkpoint.py) and the PR 4 prefetch/lag-1
loop holds in-flight state:

1. run one uninterrupted **reference** training subprocess to completion
   and fingerprint its final checkpoint (CRC32 of the serialized state
   and of the data-iterator position — flax msgpack bytes are
   deterministic, so bit-equality of the files IS bit-equality of
   params/opt-state/step/iterator position);
2. repeatedly launch the same run in a **crash** directory and kill it
   with the seeded ``kill@K`` FaultPlan action at a randomized batch
   ordinal — including rounds throttled with ``NTXENT_CKPT_SLOW_MS`` so
   the SIGKILL provably lands **mid-save** (a staging dir is on disk at
   death);
3. after every kill, assert the checkpoint dir holds **no torn step**
   (every step dir is complete and CRC-clean; abandoned ``.tmp-*``
   staging dirs are the only debris and the next incarnation purges
   them);
4. run a final incarnation to completion and assert its final
   checkpoint is **bit-identical** to the reference's.

``scripts/crash_audit.sh`` is the one-command wrapper; a pytest
(slow-tier) drives a smaller version of the same loop. This module
deliberately imports no JAX — the harness must stay light enough to
orchestrate subprocesses without paying backend init itself.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import os
import random
import signal
import socket
import subprocess
import sys
import time
import zlib
from collections.abc import Sequence
from pathlib import Path

logger = logging.getLogger(__name__)

__all__ = ["CrashAudit", "CrashAuditError", "AuditReport",
           "checkpoint_fingerprint", "scan_checkpoint_dir",
           "losses_from_jsonl", "restore_reshards_from_jsonl",
           "parse_schedule"]

_TMP_PREFIX = ".tmp-"
_STATE_FILE = "state.msgpack"
_DATA_STATE_FILE = "data_state.json"


class CrashAuditError(AssertionError):
    """An audit invariant failed (torn step, inexact resume, ...)."""


def _rmtree(path: Path) -> None:
    import shutil

    shutil.rmtree(path, ignore_errors=True)


def _crc32_file(path: Path, chunk: int = 1 << 20) -> int:
    value = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                return value
            value = zlib.crc32(block, value)


def _step_dirs(ckpt_dir: Path) -> dict[int, Path]:
    out: dict[int, Path] = {}
    if not ckpt_dir.is_dir():
        return out
    for p in ckpt_dir.iterdir():
        if p.is_dir() and not p.name.startswith(_TMP_PREFIX) \
                and p.name.isdigit():
            out[int(p.name)] = p
    return out


def checkpoint_fingerprint(ckpt_dir: Path, step: int) -> dict:
    """CRC32 fingerprint of one step's payload files. Serialization is
    deterministic, so two runs that agree here agree on every param,
    optimizer moment, the global step, and the iterator position."""
    step_dir = _step_dirs(Path(ckpt_dir)).get(int(step))
    if step_dir is None:
        raise CrashAuditError(
            f"no checkpoint for step {step} under {ckpt_dir}")
    fp = {}
    for name in (_STATE_FILE, _DATA_STATE_FILE):
        p = step_dir / name
        if p.exists():
            fp[name] = [p.stat().st_size, _crc32_file(p)]
    if _STATE_FILE not in fp:
        raise CrashAuditError(f"step {step} under {ckpt_dir} has no "
                              f"{_STATE_FILE}")
    return fp


def scan_checkpoint_dir(ckpt_dir: Path) -> dict:
    """Post-mortem scan: ``torn`` steps (incomplete, or CRC-mismatching
    their manifest entry) and leftover ``tmp`` staging dirs.

    Atomic writes make ``torn == []`` the invariant a kill at ANY instant
    must preserve; ``tmp`` debris is legal immediately after a mid-save
    kill (it proves the kill WAS mid-save) and must be gone after the
    next incarnation's manager init.
    """
    ckpt_dir = Path(ckpt_dir)
    torn: list[str] = []
    try:
        with open(ckpt_dir / "manifests.json") as f:
            manifests = json.load(f)
    except (OSError, json.JSONDecodeError):
        manifests = {}
    for step, step_dir in sorted(_step_dirs(ckpt_dir).items()):
        if not (step_dir / _STATE_FILE).exists():
            torn.append(f"{step}: missing {_STATE_FILE}")
            continue
        recorded = manifests.get(str(step))
        if recorded is None:
            continue  # complete-but-unmanifested (killed pre-manifest)
        for rel, (size, crc) in recorded["files"].items():
            p = step_dir / rel
            if not p.exists() or p.stat().st_size != size \
                    or _crc32_file(p) != crc:
                torn.append(f"{step}: {rel} fails manifest check")
                break
    tmp = sorted(p.name for p in ckpt_dir.iterdir()
                 if p.is_dir() and p.name.startswith(_TMP_PREFIX)) \
        if ckpt_dir.is_dir() else []
    return {"torn": torn, "tmp": tmp}


def _read_events(path: Path, event: str) -> list[dict]:
    """obs.events.read_events (tolerant JSONL parse — a killed
    incarnation may die mid-write of its last line), loaded BY FILE PATH
    so this harness stays JAX-free (the bench.py idiom: the package
    __init__ would pull the full framework). Missing file -> []."""
    import importlib.util

    events_path = Path(__file__).resolve().parent.parent / "obs" / \
        "events.py"
    spec = importlib.util.spec_from_file_location("_ntxent_obs_events",
                                                  events_path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    try:
        return module.read_events(str(path), event)
    except OSError:
        return []


def losses_from_jsonl(path: Path) -> dict[int, float]:
    """{global step: loss} from an obs JSONL event log (``step`` events
    carry GLOBAL step numbers, so curves from resumed incarnations merge
    by key)."""
    return {int(rec["step"]): float(rec["loss"])
            for rec in _read_events(path, "step")
            if "step" in rec and "loss" in rec}


def restore_reshards_from_jsonl(path: Path) -> list[str]:
    """The ``reshard`` field of every checkpoint-restore event in a JSONL
    log — the structured proof a topology-changed incarnation re-placed
    state instead of crashing."""
    return [str(rec.get("reshard"))
            for rec in _read_events(path, "checkpoint")
            if rec.get("action") == "restore"]


def parse_schedule(spec: str) -> list[tuple[int, int]]:
    """Parse an elastic schedule: ``"8,4x2,8"`` -> ``[(8, 1), (4, 2),
    (8, 1)]``. Each entry is a TOTAL simulated device count, optionally
    ``xP`` to spread it over P coordinated OS processes (``--coordinator``
    rendezvous, devices split evenly — the first step beyond
    single-process topology changes, ROADMAP item 5)."""
    out: list[tuple[int, int]] = []
    for item in filter(None, (s.strip() for s in spec.split(","))):
        dev, _, procs = item.partition("x")
        try:
            d = int(dev)
            p = int(procs) if procs else 1
        except ValueError:
            raise ValueError(
                f"bad schedule entry {item!r}: expected DEVICES or "
                f"DEVICESxPROCESSES, e.g. '8' or '4x2'") from None
        if d < 1 or p < 1 or d % p:
            raise ValueError(
                f"bad schedule entry {item!r}: devices must be a "
                f"positive multiple of processes (got {d} over {p})")
        out.append((d, p))
    if not out:
        raise ValueError(f"empty schedule {spec!r}")
    return out


@dataclasses.dataclass
class AuditReport:
    kills: int = 0
    midsave_kills: int = 0
    completed_early: int = 0
    bitexact_completions: int = 0
    rounds: list = dataclasses.field(default_factory=list)
    final_step: int | None = None
    bit_exact: bool = False
    reference_fingerprint: dict = dataclasses.field(default_factory=dict)
    survivor_fingerprint: dict = dataclasses.field(default_factory=dict)
    elapsed_s: float = 0.0

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)


class CrashAudit:
    """Drive the kill → scan → resume → verify loop against the CLI.

    One audit = one reference run + ``kills`` killed incarnations (the
    first ``midsave`` of them throttled so the SIGKILL lands inside a
    checkpoint write) + one final clean incarnation, all sharing the
    crash directory. ``steps`` stays tiny (CPU, tiny model) so the whole
    audit fits the <60 s budget of ``scripts/crash_audit.sh``.
    """

    def __init__(self, workdir: str | Path, steps: int = 8,
                 seed: int = 0, batch: int = 8, image_size: int = 8,
                 timeout_s: float = 180.0, slow_save_ms: int = 400):
        self.workdir = Path(workdir)
        self.workdir.mkdir(parents=True, exist_ok=True)
        self.steps = int(steps)
        self.seed = int(seed)
        self.batch = int(batch)
        self.image_size = int(image_size)
        self.timeout_s = float(timeout_s)
        self.slow_save_ms = int(slow_save_ms)
        self.rng = random.Random(seed)

    # -- one training incarnation ----------------------------------------
    def _cmd(self, ckpt_dir: Path, chaos: str | None,
             log_jsonl: Path | None = None) -> list[str]:
        cmd = [sys.executable, "-m", "ntxent_tpu.cli",
               "--platform", "cpu",
               "--dataset", "synthetic",
               "--synthetic-samples", str(max(64, 2 * self.batch)),
               "--image-size", str(self.image_size),
               "--model", "tiny", "--proj-hidden-dim", "16",
               "--proj-dim", "8",
               "--batch", str(self.batch),
               "--steps", str(self.steps),
               "--warmup-steps", "1",
               "--seed", str(self.seed),
               "--ckpt-dir", str(ckpt_dir),
               "--ckpt-every", "1",
               "--ckpt-keep-last", "0",  # the audit compares EVERY step
               "--async-ckpt",
               "--log-every", "1"]
        if chaos:
            cmd += ["--chaos", chaos]
        if log_jsonl is not None:
            cmd += ["--log-jsonl", str(log_jsonl)]
        return cmd

    def _env(self, slow_save: bool,
             local_device_count: int | None) -> dict:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        if local_device_count is not None and local_device_count > 1:
            # The subprocess boundary IS the elastic boundary: simulated
            # device count is fixed at backend init, so shrink/grow
            # across incarnations means a different XLA_FLAGS per launch.
            env["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                                f"{local_device_count}")
        if slow_save:
            env["NTXENT_CKPT_SLOW_MS"] = str(self.slow_save_ms)
        else:
            env.pop("NTXENT_CKPT_SLOW_MS", None)
        return env

    def _run(self, ckpt_dir: Path, chaos: str | None = None,
             slow_save: bool = False,
             device_count: int | None = None,
             log_jsonl: Path | None = None,
             process_count: int = 1) -> tuple[int, str]:
        if process_count > 1:
            return self._run_multiprocess(ckpt_dir, chaos=chaos,
                                          device_count=device_count or 1,
                                          process_count=process_count,
                                          log_jsonl=log_jsonl)
        env = self._env(slow_save, device_count)
        proc = subprocess.run(
            self._cmd(ckpt_dir, chaos, log_jsonl=log_jsonl), env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            timeout=self.timeout_s)
        return proc.returncode, proc.stdout or ""

    def _run_multiprocess(self, ckpt_dir: Path, chaos: str | None,
                          device_count: int, process_count: int,
                          log_jsonl: Path | None) -> tuple[int, str]:
        """One incarnation as P coordinated OS processes (the real
        multi-host shape): rendezvous via ``--coordinator`` on a free
        localhost port, ``device_count`` simulated devices split evenly.

        Every process runs the SAME chaos plan against the same seeded
        batch schedule, so a ``kill@K`` drops the whole world at the
        same batch ordinal — the coordinated-crash case a pod-level
        preemption actually delivers. Process 0 owns the JSONL (loss is
        replicated) and its exit code is the incarnation's verdict; a
        straggler that outlives the timeout is killed and reported.
        """
        local_devices = device_count // process_count
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            coordinator = f"127.0.0.1:{s.getsockname()[1]}"
        env = self._env(False, local_devices)
        procs = []
        for pid in range(process_count):
            cmd = self._cmd(ckpt_dir, chaos,
                            log_jsonl=log_jsonl if pid == 0 else None)
            cmd += ["--coordinator", coordinator,
                    "--num-processes", str(process_count),
                    "--process-id", str(pid)]
            procs.append(subprocess.Popen(
                cmd, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))
        deadline = time.monotonic() + self.timeout_s
        rcs: list[int | None] = [None] * process_count
        outs: list[str] = [""] * process_count
        for i, proc in enumerate(procs):
            try:
                outs[i] = proc.communicate(
                    timeout=max(0.1, deadline - time.monotonic()))[0] \
                    or ""
                rcs[i] = proc.returncode
            except subprocess.TimeoutExpired:
                proc.kill()
                outs[i] = (proc.communicate()[0] or "") + \
                    "\n[crashsim: straggler killed at timeout]"
                rcs[i] = proc.returncode
        combined = "\n".join(
            f"--- process {i} (rc={rcs[i]}) ---\n{out}"
            for i, out in enumerate(outs))
        if chaos is None:
            # A clean incarnation must complete on EVERY rank.
            rc = next((r for r in rcs if r != 0), 0)
        else:
            rc = rcs[0]
        return rc, combined

    # -- the audit --------------------------------------------------------
    def run_reference(self) -> dict:
        ref_dir = self.workdir / "ref"
        rc, out = self._run(ref_dir)
        if rc != 0:
            raise CrashAuditError(
                f"reference run failed rc={rc}:\n{out[-2000:]}")
        return checkpoint_fingerprint(ref_dir, self.steps)

    def _finish_and_verify(self, crash_dir: Path, report: AuditReport,
                           reference_fp: dict) -> None:
        """Run the crash dir to completion (if it is not already there)
        and hold its final checkpoint against the reference CRCs."""
        latest = max(_step_dirs(crash_dir), default=0)
        if latest < self.steps:
            rc, out = self._run(crash_dir)
            if rc != 0:
                raise CrashAuditError(
                    f"survivor run failed rc={rc}:\n{out[-2000:]}")
        scan = scan_checkpoint_dir(crash_dir)
        if scan["torn"] or scan["tmp"]:
            raise CrashAuditError(f"survivor left debris: {scan}")
        report.final_step = max(_step_dirs(crash_dir))
        if report.final_step != self.steps:
            raise CrashAuditError(
                f"survivor finished at step {report.final_step}, "
                f"wanted {self.steps}")
        report.survivor_fingerprint = checkpoint_fingerprint(
            crash_dir, self.steps)
        if report.survivor_fingerprint != reference_fp:
            raise CrashAuditError(
                "survivor's final checkpoint differs from the "
                f"uninterrupted reference:\nref      = "
                f"{reference_fp}\nsurvivor = "
                f"{report.survivor_fingerprint}")
        report.bitexact_completions += 1
        report.bit_exact = True

    def _run_lineage(self, name: str, kills: int, midsave: int,
                     rng: random.Random, ref_fp) -> AuditReport:
        """One independent kill→scan→resume lineage in its own crash
        dir. ``ref_fp`` is a zero-arg callable yielding the reference
        fingerprint (a future: the reference run executes concurrently)."""
        report = AuditReport()
        crash_dir = self.workdir / name
        round_no = 0
        while report.kills < kills or report.midsave_kills < midsave:
            round_no += 1
            if round_no > (kills + midsave) * 6:
                raise CrashAuditError(
                    f"{name}: could not land {kills} kills in "
                    f"{round_no} rounds")
            latest = max(_step_dirs(crash_dir), default=0)
            remaining = self.steps - latest
            if remaining < 3:
                # This lifecycle is (nearly) done: wipe it and start a
                # fresh one, restoring the full randomization range for
                # the next kill point. Every kill already asserted the
                # no-torn invariant, and the lineage's FINAL lifecycle
                # (below) is the one driven to a verified bit-exact
                # completion — finishing every intermediate chain too
                # would double the audit's subprocess count for a
                # duplicate of that check.
                _rmtree(crash_dir)
                continue
            # Kill point randomized over the steps THIS incarnation will
            # actually run (it resumes at the newest step on disk).
            # k >= 2 leaves batch 1's step time for a pending save to
            # land, so lineages make progress with high probability; the
            # round cap above bounds the unlucky tail.
            k = rng.randint(2, remaining)
            slow = report.midsave_kills < midsave
            rc, out = self._run(crash_dir, chaos=f"kill@{k}",
                                slow_save=slow)
            if rc == 0:
                # The kill ordinal never fired (run completed first) —
                # still a resume check, not a kill.
                report.completed_early += 1
                self._finish_and_verify(crash_dir, report, ref_fp())
                _rmtree(crash_dir)
                continue
            if rc != -signal.SIGKILL and rc != 128 + signal.SIGKILL:
                raise CrashAuditError(
                    f"{name} round {round_no}: expected SIGKILL death, "
                    f"got rc={rc}:\n{out[-2000:]}")
            scan = scan_checkpoint_dir(crash_dir)
            if scan["torn"]:
                raise CrashAuditError(
                    f"{name} round {round_no}: torn checkpoint step(s) "
                    f"after SIGKILL: {scan['torn']}")
            mid = bool(scan["tmp"])
            report.kills += 1
            report.midsave_kills += int(mid)
            report.rounds.append({"lineage": name, "round": round_no,
                                  "kill_at": latest + k,
                                  "outcome": "killed",
                                  "midsave": mid, **scan})
            logger.info("%s round %d: kill@%d ok (midsave=%s, steps on "
                        "disk=%s)", name, round_no, latest + k, mid,
                        sorted(_step_dirs(crash_dir)))
        # Survivor: this lineage's dir runs to completion for its final
        # bit-exactness verdict.
        self._finish_and_verify(crash_dir, report, ref_fp())
        self._write_summary(f"summary_{name}.json", {
            "lineage": name, "mode": "kill",
            "kills": report.kills,
            "midsave_kills": report.midsave_kills,
            "restarts": report.kills + report.completed_early,
            "device_counts": [1] * (report.kills
                                    + report.completed_early + 1),
            "rounds": report.rounds,
            "final_step": report.final_step,
            "crc_exact": report.bit_exact,
            "verdict": "PASS:bitexact" if report.bit_exact
            else "FAIL:crc_mismatch",
        })
        return report

    def _write_summary(self, name: str, payload: dict) -> Path:
        """Atomically write a structured per-lineage JSON artifact —
        what crash_audit.sh / elastic_smoke.sh assert on instead of
        grepping logs."""
        path = self.workdir / name
        tmp = path.with_suffix(".tmp")
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
        os.replace(tmp, path)
        return path

    def audit(self, kills: int = 5, midsave: int = 1,
              lineages: int = 2) -> AuditReport:
        """Run the reference and ``lineages`` independent kill lineages
        concurrently (subprocesses bound the parallelism; each lineage
        owns its crash dir, so rounds only serialize within a lineage).
        The mid-save quota rides lineage 0 (its early rounds throttle the
        writer until a kill provably lands inside a write)."""
        import concurrent.futures as cf

        t0 = time.monotonic()
        lineages = max(1, min(int(lineages), kills))
        quotas = [kills // lineages] * lineages
        for i in range(kills % lineages):
            quotas[i] += 1
        with cf.ThreadPoolExecutor(max_workers=lineages + 1) as pool:
            ref_future = pool.submit(self.run_reference)
            lineage_futures = [
                pool.submit(self._run_lineage, f"crash{i}", quotas[i],
                            midsave if i == 0 else 0,
                            random.Random(self.seed * 1000 + i),
                            ref_future.result)
                for i in range(lineages)]
            reports = [f.result() for f in lineage_futures]
            reference_fp = ref_future.result()

        report = AuditReport()
        report.reference_fingerprint = reference_fp
        lineage_summaries = []
        for i, sub in enumerate(reports):
            lineage_summaries.append({
                "lineage": f"crash{i}", "kills": sub.kills,
                "midsave_kills": sub.midsave_kills,
                "restarts": sub.kills + sub.completed_early,
                "final_step": sub.final_step,
                "crc_exact": sub.bit_exact})
            report.kills += sub.kills
            report.midsave_kills += sub.midsave_kills
            report.completed_early += sub.completed_early
            report.bitexact_completions += sub.bitexact_completions
            report.rounds.extend(sub.rounds)
            report.final_step = sub.final_step
            report.survivor_fingerprint = sub.survivor_fingerprint
        report.bit_exact = all(sub.bit_exact for sub in reports)
        if report.midsave_kills < midsave:
            raise CrashAuditError(
                f"only {report.midsave_kills}/{midsave} kills landed "
                "mid-save (no staging dir observed at death)")
        report.elapsed_s = round(time.monotonic() - t0, 2)
        self._write_summary("audit_summary.json", {
            "mode": "kill",
            "kills": report.kills,
            "midsave_kills": report.midsave_kills,
            "restarts": report.kills + report.completed_early,
            "lineages": lineage_summaries,
            "final_step": report.final_step,
            "crc_exact": report.bit_exact,
            "reference_fingerprint": report.reference_fingerprint,
            "survivor_fingerprint": report.survivor_fingerprint,
            "elapsed_s": report.elapsed_s,
            "verdict": "PASS:bitexact" if report.bit_exact
            else "FAIL:crc_mismatch",
        })
        return report

    # -- the elastic audit -------------------------------------------------
    def elastic(self, schedule: Sequence = (8, 4, 8),
                rtol: float = 0.05, atol: float = 0.02) -> dict:
        """Shrink/grow chaos lineage: ``kill@K`` then restore across a
        changing simulated-device schedule, loss-curve continuity
        asserted against an uninterrupted reference on the full mesh.

        One reference run executes the whole job on ``schedule[0]``
        devices; the elastic lineage then runs one incarnation per
        schedule entry — every non-final incarnation is SIGKILLed at a
        seeded-random batch ordinal, and each successor launches with a
        DIFFERENT ``--xla_force_host_platform_device_count`` (the
        subprocess boundary is where real fleets change size), restoring
        the previous world's checkpoint onto its own mesh.

        A schedule entry may also be a ``(devices, processes)`` pair
        (the ``"4x2"`` CLI syntax, ``parse_schedule``): that incarnation
        runs as P coordinated OS processes rendezvousing through
        ``--coordinator`` with ``devices/P`` simulated devices each — so
        the lineage can change PROCESS topology across a death, not just
        device count (a ``kill@K`` entry drops all P ranks at the same
        batch ordinal; the successor restores their world onto its own
        process count). Asserts after
        every death: no torn steps; across the lineage: at least one
        restore re-sharded (``reshard="gather_replace"`` in the JSONL
        restore events — the topology sidecar worked), the final step was
        reached, and every step's loss matches the reference within
        ``rtol``/``atol`` (the global batch is device-count-invariant;
        only reduction order may differ). Bit-exactness is REPORTED, not
        asserted — psum order across different mesh sizes is allowed to
        move float ulps, which is exactly why the assert is on the loss
        curve. Writes ``elastic_summary.json`` and returns it.
        """
        t0 = time.monotonic()
        rng = random.Random(self.seed * 7919 + 1)
        norm: list[tuple[int, int]] = [
            (int(e), 1) if not isinstance(e, (tuple, list))
            else (int(e[0]), int(e[1]))
            for e in schedule]
        ref_dir = self.workdir / "elastic_ref"
        ref_jsonl = self.workdir / "elastic_ref.jsonl"
        rc, out = self._run(ref_dir, device_count=norm[0][0],
                            log_jsonl=ref_jsonl)
        if rc != 0:
            raise CrashAuditError(
                f"elastic reference run failed rc={rc}:\n{out[-2000:]}")
        ref_losses = losses_from_jsonl(ref_jsonl)
        if len(ref_losses) < self.steps:
            raise CrashAuditError(
                f"elastic reference logged {len(ref_losses)} step "
                f"events, wanted {self.steps}")

        crash_dir = self.workdir / "elastic0"
        incarnations: list[dict] = []
        kills = 0
        merged_losses: dict[int, float] = {}
        for i, (devices, processes) in enumerate(norm):
            final = i == len(norm) - 1
            latest = max(_step_dirs(crash_dir), default=0)
            jsonl = self.workdir / f"elastic0_run{i}.jsonl"
            chaos = None
            if not final:
                remaining = self.steps - latest
                if remaining <= 2:
                    raise CrashAuditError(
                        f"elastic incarnation {i} has only {remaining} "
                        "steps left to kill inside; raise --steps")
                # Leave >= 1 step for the next incarnation to TRAIN on
                # its changed mesh (a restore-only hop would still
                # re-shard, but prove less).
                chaos = f"kill@{rng.randint(2, max(2, remaining - 2))}"
            rc, out = self._run(crash_dir, chaos=chaos,
                                device_count=devices, log_jsonl=jsonl,
                                process_count=processes)
            scan = scan_checkpoint_dir(crash_dir)
            if scan["torn"]:
                raise CrashAuditError(
                    f"elastic incarnation {i} ({devices} devices): torn "
                    f"checkpoint step(s): {scan['torn']}")
            if chaos is None:
                if rc != 0:
                    raise CrashAuditError(
                        f"elastic survivor failed rc={rc}:\n{out[-2000:]}")
            elif rc in (-signal.SIGKILL, 128 + signal.SIGKILL):
                kills += 1
            elif rc != 0:
                raise CrashAuditError(
                    f"elastic incarnation {i}: expected SIGKILL death or "
                    f"completion, got rc={rc}:\n{out[-2000:]}")
            merged_losses.update(losses_from_jsonl(jsonl))
            incarnations.append({
                "devices": int(devices), "processes": int(processes),
                "chaos": chaos, "rc": rc,
                "resumed_from": latest,
                "reshards": restore_reshards_from_jsonl(jsonl)})
            logger.info("elastic incarnation %d: devices=%d processes=%d "
                        "chaos=%s rc=%s resumed_from=%d", i, devices,
                        processes, chaos, rc, latest)

        final_step = max(_step_dirs(crash_dir), default=0)
        if final_step != self.steps:
            raise CrashAuditError(
                f"elastic lineage finished at step {final_step}, wanted "
                f"{self.steps}")
        reshards = [r for inc in incarnations[1:] for r in inc["reshards"]]
        if "gather_replace" not in reshards:
            raise CrashAuditError(
                "no topology re-shard observed across the device "
                f"schedule {tuple(norm)} (restore events: {reshards})")
        compared = sorted(set(merged_losses) & set(ref_losses))
        if len(compared) < self.steps // 2:
            raise CrashAuditError(
                f"only {len(compared)} comparable steps between elastic "
                "and reference loss curves")
        worst_step, worst_abs, worst_rel, continuity_ok = None, 0.0, 0.0, True
        for s in compared:
            diff = abs(merged_losses[s] - ref_losses[s])
            rel = diff / max(1e-9, abs(ref_losses[s]))
            if diff > worst_abs:
                worst_step, worst_abs, worst_rel = s, diff, rel
            if diff > atol + rtol * abs(ref_losses[s]):
                continuity_ok = False
        try:
            ref_fp = checkpoint_fingerprint(ref_dir, self.steps)
            got_fp = checkpoint_fingerprint(crash_dir, self.steps)
            crc_exact = ref_fp == got_fp
        except CrashAuditError:
            ref_fp, got_fp, crc_exact = {}, {}, False
        summary = {
            "lineage": "elastic0", "mode": "elastic",
            "device_schedule": [d for d, _ in norm],
            "process_schedule": [p for _, p in norm],
            "kills": kills,
            "restarts": len(incarnations) - 1,
            "device_counts": [inc["devices"] for inc in incarnations],
            "incarnations": incarnations,
            "final_step": final_step,
            "crc_exact": crc_exact,
            "reference_fingerprint": ref_fp,
            "survivor_fingerprint": got_fp,
            "loss_continuity": {
                "steps_compared": len(compared),
                "worst_step": worst_step,
                "max_abs_diff": round(worst_abs, 6),
                "rel_at_worst": round(worst_rel, 6),
                "rtol": rtol, "atol": atol,
                "ok": continuity_ok,
            },
            "elapsed_s": round(time.monotonic() - t0, 2),
            "verdict": "PASS:loss_continuity" if continuity_ok
            else "FAIL:loss_divergence",
        }
        self._write_summary("elastic_summary.json", summary)
        if not continuity_ok:
            raise CrashAuditError(
                "elastic loss curve diverged from the uninterrupted "
                f"reference: step {worst_step} differs by {worst_abs} "
                f"(rel {worst_rel:.4f}); see elastic_summary.json")
        return summary


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="Crash-replay audit: kill a real training run at "
                    "randomized points (incl. mid-save) and prove "
                    "bit-exact resume — or, with --mode elastic, kill "
                    "across a shrink/grow device schedule and prove "
                    "loss-curve continuity through re-sharded restores.")
    parser.add_argument("--workdir", required=True)
    parser.add_argument("--mode", default="kill",
                        choices=["kill", "elastic"])
    parser.add_argument("--steps", type=int, default=8)
    parser.add_argument("--kills", type=int, default=5)
    parser.add_argument("--midsave", type=int, default=1)
    parser.add_argument("--schedule", default="8,4,8",
                        help="elastic mode: comma list of simulated "
                             "device counts, one incarnation each; every "
                             "non-final one is SIGKILLed mid-run. An "
                             "entry DxP (e.g. 4x2) runs that incarnation "
                             "as P coordinated OS processes with D/P "
                             "devices each (multi-process topology "
                             "change)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--timeout-s", type=float, default=180.0)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO,
                        format="%(levelname)s %(message)s")
    audit = CrashAudit(args.workdir, steps=args.steps, seed=args.seed,
                       timeout_s=args.timeout_s)
    try:
        if args.mode == "elastic":
            summary = audit.elastic(
                schedule=parse_schedule(args.schedule))
            print(json.dumps(summary, indent=2, sort_keys=True))
            print(f"elastic audit: OK — schedule "
                  f"{summary['device_schedule']} over processes "
                  f"{summary['process_schedule']}, {summary['kills']} "
                  f"kills, loss continuity over "
                  f"{summary['loss_continuity']['steps_compared']} steps "
                  f"(max abs diff "
                  f"{summary['loss_continuity']['max_abs_diff']}) in "
                  f"{summary['elapsed_s']}s")
            return 0
        report = audit.audit(kills=args.kills, midsave=args.midsave)
    except CrashAuditError as e:
        print(f"CRASH AUDIT FAILED: {e}", file=sys.stderr)
        return 1
    print(report.to_json())
    print(f"crash audit: OK — {report.kills} kills "
          f"({report.midsave_kills} mid-save), resume bit-exact at "
          f"step {report.final_step} in {report.elapsed_s}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
