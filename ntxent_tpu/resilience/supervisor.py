"""Self-healing training supervisor: detectors in, restarts out.

The framework already shipped the *detectors* — ``PreemptionGuard``
(SIGTERM → stop-at-step-boundary + force checkpoint),
``StallWatchdog`` (silence → stack dumps), checksum-verified
checkpoints (training/checkpoint.py) and the in-step divergence guard
(guard.py) — but each one ended at a log line. ``Supervisor.run()`` closes
the loop: it runs attempts of the training job and, on any fault the
detectors surface, restarts IN-PROCESS from the newest valid checkpoint,
up to ``max_restarts`` times with exponential backoff:

* **clean-but-incomplete exit** (SIGTERM during chaos testing, stall
  escalation, a data pipeline that stopped) → restart; ``fit`` restores
  the force-saved step, so a kill at step k resumes at k;
* **exception** (``DivergenceError`` rollback, ``ChaosError``, transient
  IO that out-lived its RetryPolicy) → restart; the crashed attempt wrote
  no final checkpoint, so restore lands on the last healthy save — and if
  THAT file is truncated/corrupt, restore's checksum fallback walks back
  to the newest valid one;
* **stall** → the watchdog's one-shot ``on_stall`` asks the current
  attempt's PreemptionGuard to stop; the attempt checkpoints and exits at
  the next step boundary and the supervisor restarts it (the
  watchdog-to-supervisor escalation utils/watchdog.py documents).

The caller supplies ``run_attempt(attempt, stop_fn, watchdog)`` — usually
a closure over ``trainer.fit`` that builds a FRESH TrainState template per
attempt (donated buffers from a crashed attempt must not be reused) and
passes ``stop_fn``/``watchdog`` through. ``ntxent_tpu.cli`` wires exactly
that for ``--max-restarts``/``--chaos``/``--nan-policy``.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from collections.abc import Callable

from ..obs import events as obs_events
from ..obs.registry import default_registry
from ..training.preemption import PreemptionGuard
from ..utils.watchdog import StallWatchdog
from .faults import TopologyChange
from .retry import RetryPolicy

logger = logging.getLogger(__name__)

_RESTARTS = default_registry().counter(
    "supervisor_restarts_total",
    "in-process restarts after a detected fault")
_ATTEMPTS = default_registry().counter(
    "supervisor_attempts_total", "supervised attempts started")
_TOPOLOGY_RESTARTS = default_registry().counter(
    "supervisor_topology_restarts_total",
    "restarts that rebuilt the mesh over a changed device set "
    "(shrink@K / grow@K)")

__all__ = ["AttemptRecord", "Supervisor", "SupervisorResult"]


@dataclasses.dataclass(frozen=True)
class AttemptRecord:
    attempt: int
    # Step the attempt actually reached; None when it died on an exception
    # before returning a state (a crashed attempt's progress is unknown —
    # reporting the previous attempt's step here would be a lie).
    end_step: int | None
    preempted: bool
    stalled: bool
    error: str | None
    # The topology action ("shrink"/"grow") that ended this attempt, None
    # for every other exit: elastic restarts are visible in the records,
    # not just in the mesh the next attempt happens to build.
    topology: str | None = None


@dataclasses.dataclass
class SupervisorResult:
    completed: bool
    state: object
    histories: list
    records: list

    @property
    def history(self):
        """Concatenated per-attempt histories (rollbacks may repeat
        step numbers across attempt boundaries)."""
        return [entry for h in self.histories for entry in h]


class Supervisor:
    """Restart-with-backoff harness around an attempt callable.

    ``run_attempt(attempt, stop_fn, watchdog) -> (state, history)`` runs
    one incarnation of the job (typically ``trainer.fit`` with
    ``checkpoint_dir`` set so every incarnation resumes itself).
    Completion = ``int(state.step) >= num_steps``.

    ``backoff`` is a resilience.RetryPolicy used only for its delay
    schedule (seeded jitter included). ``stall_timeout_s`` arms a
    StallWatchdog per attempt whose escalation stops the attempt cleanly.
    ``injector`` (faults.FaultInjector) gets a between-attempts hook —
    that is where the chaos plan's checkpoint-truncation fault fires.

    ``topology_hook(action)`` is the elastic-restart seam: when an
    attempt dies with ``faults.TopologyChange`` (the ``shrink@K`` /
    ``grow@K`` plan actions — or a real resource manager surfacing a
    pool change the same way), the hook runs BEFORE the next attempt and
    must rebuild the world for it — mesh over the new device set, train
    step compiled for that mesh, data pipeline bound to its sharding
    (``ntxent_tpu.cli`` wires exactly that for the data-parallel branch).
    The next attempt then restores the newest valid checkpoint onto the
    rebuilt mesh; the checkpoint layer's topology sidecar makes that a
    re-shard, not a crash. Without a hook, a topology fault restarts
    onto the unchanged world (logged — the fault then only proved the
    restart path).
    """

    def __init__(self, run_attempt: Callable, num_steps: int,
                 checkpoint_dir=None, max_restarts: int = 3,
                 backoff: RetryPolicy | None = None,
                 stall_timeout_s: float | None = None,
                 injector=None,
                 topology_hook: Callable[[str], None] | None = None,
                 sleep: Callable[[float], None] = time.sleep):
        if max_restarts < 0:
            raise ValueError(f"max_restarts must be >= 0, "
                             f"got {max_restarts}")
        self.run_attempt = run_attempt
        self.num_steps = int(num_steps)
        self.checkpoint_dir = checkpoint_dir
        self.max_restarts = max_restarts
        self.backoff = backoff or RetryPolicy(
            max_attempts=max_restarts + 1, base_delay_s=1.0,
            multiplier=2.0, max_delay_s=60.0, jitter=0.1)
        self.stall_timeout_s = stall_timeout_s
        self.injector = injector
        self.topology_hook = topology_hook
        self.sleep = sleep
        self._guard: PreemptionGuard | None = None

    def _on_stall(self, quiet_s: float) -> None:
        guard = self._guard
        if guard is None:  # stall latched between attempts: nothing to stop
            return
        logger.error("supervisor: stall escalation after %.1fs of silence "
                     "— stopping the attempt at the next step boundary "
                     "(checkpoint + in-process restart)", quiet_s)
        # Flight recorder (ISSUE 7): persist the event tail BEFORE the
        # restart machinery runs — a stalled attempt's last N events are
        # the postmortem, and --log-jsonl may not have been enabled.
        try:
            obs_events.dump_flight(reason=f"stall:{quiet_s:.1f}s")
        except Exception:  # the dump must never block the escalation
            logger.exception("flight recorder dump failed on stall")
        guard.request()

    def run(self) -> SupervisorResult:
        histories: list = []
        records: list[AttemptRecord] = []
        state = None
        watchdog = (StallWatchdog(timeout_s=self.stall_timeout_s,
                                  on_stall=self._on_stall)
                    if self.stall_timeout_s else None)
        total_attempts = self.max_restarts + 1
        for attempt in range(total_attempts):
            guard = PreemptionGuard()
            self._guard = guard
            _ATTEMPTS.inc()
            # Stamp subsequent event-log records with this attempt's
            # ordinal (rollback replays repeat step numbers; the attempt
            # id is what keeps the timeline unambiguous).
            obs_events.set_attempt(attempt)
            error: str | None = None
            stalled = False
            topology: str | None = None
            attempt_state = None
            if watchdog is not None:
                watchdog.reset()
                watchdog.start()
            try:
                with guard:
                    try:
                        attempt_state, history = self.run_attempt(
                            attempt, stop_fn=guard.requested,
                            watchdog=watchdog)
                        histories.append(history)
                    except TopologyChange as e:
                        # Not a crash: the world changed shape. The next
                        # attempt must run on a rebuilt mesh (hook below).
                        topology = e.action
                        error = f"TopologyChange: {e}"
                        logger.warning(
                            "supervisor: attempt %d/%d ended by a "
                            "topology %s — rebuilding the mesh before "
                            "restart", attempt + 1, total_attempts,
                            e.action)
                    except Exception as e:  # bounded by max_restarts
                        error = f"{type(e).__name__}: {e}"
                        logger.exception(
                            "supervisor: attempt %d/%d died", attempt + 1,
                            total_attempts)
            finally:
                self._guard = None
                if watchdog is not None:
                    stalled = watchdog.fired.is_set()
                    watchdog.stop()
            end_step = int(attempt_state.step) \
                if attempt_state is not None else None
            if attempt_state is not None:
                state = attempt_state
            records.append(AttemptRecord(
                attempt=attempt, end_step=end_step,
                preempted=guard.preempted, stalled=stalled, error=error,
                topology=topology))
            if error is None and not guard.preempted \
                    and end_step is not None and end_step >= self.num_steps:
                logger.info("supervisor: run complete at step %d after "
                            "%d attempt(s)", end_step, attempt + 1)
                return SupervisorResult(True, state, histories, records)
            if attempt + 1 >= total_attempts:
                break
            if topology is not None:
                if self.topology_hook is not None:
                    try:
                        self.topology_hook(topology)
                        _TOPOLOGY_RESTARTS.inc()
                    except Exception:
                        # A world that failed to rebuild is still a world:
                        # restart on the old one rather than giving up.
                        logger.exception(
                            "supervisor: topology hook failed for %r — "
                            "restarting on the unchanged mesh", topology)
                else:
                    logger.warning(
                        "supervisor: topology %s with no topology_hook — "
                        "restarting on the unchanged mesh", topology)
            if self.injector is not None:
                self.injector.between_attempts(self.checkpoint_dir)
            delay = self.backoff.delay_for(attempt + 1)
            _RESTARTS.inc()
            obs_events.emit(
                "restart", attempt=attempt, end_step=end_step,
                preempted=bool(guard.preempted), stalled=bool(stalled),
                error=error, topology=topology, delay_s=round(delay, 4))
            logger.warning(
                "supervisor: attempt %d/%d ended at step %s "
                "(preempted=%s, stalled=%s, error=%s) — restarting from "
                "the last valid checkpoint in %.1fs", attempt + 1,
                total_attempts,
                "<unknown: attempt crashed>" if end_step is None
                else end_step, guard.preempted, stalled, error, delay)
            self.sleep(delay)
        logger.error(
            "supervisor: giving up after %d attempt(s) (last step %s of "
            "%d) — restart budget exhausted", total_attempts,
            records[-1].end_step if records else 0, self.num_steps)
        return SupervisorResult(False, state, histories, records)
